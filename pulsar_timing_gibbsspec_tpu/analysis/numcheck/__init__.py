"""numcheck — static precision-flow auditor over traced jaxprs.

jaxprcheck's C3 counts dots per dtype; numcheck tracks *flow*: where
every f64-born value is narrowed to f32, and whether the narrowed value
later feeds a reduction, a factorization, or a matmul accumulation.
Five rules over the committed entry builders
(:mod:`..jaxprcheck.entries`):

- **N1 silent-downcast-into-accumulation** — a ``convert_element_type``
  f64→f32 outside every declared mixed-precision island whose result
  reaches a reduce/Cholesky/solve/dot-contraction sink (the one-line
  ``.astype`` that silently biases a posterior).
- **N2 unpinned-reassociation** — a reassociation-sensitive reduction
  (``reduce_sum``-class over fp, or a scan-carried fp accumulation)
  whose summation order is not pinned by a ``declared_orders`` contract
  entry (the PR 8 segmented-Gram order note, machine-checked).
- **N3 tf32-hazard** — an f32 ``dot_general`` with default precision
  consuming data that was ever f64 (on GPU the MXU would run it in
  tf32, 10-bit mantissa, silently).
- **N4 missing-exact-body** — every f32 steady sweep body must have a
  registered paired f64 exact body with an identical shape signature,
  and the refresh cadence must be declared in-contract (the PR 3
  ``_chunk_fn`` pair, promoted from convention to checked property).
- **N5 error-ledger drift** — the first-order op-count ULP bound per
  source block (joined with the cost model's FLOP attribution) drifted
  past the contract pin: mixed-precision changes must re-pin the
  ledger, not assert safety in prose.

Contracts are ``contracts/*.json`` files with ``"tool": "numcheck"``;
findings ratchet against ``numcheck_baseline.json`` with racecheck's
justified-baseline semantics (TODO stubs rejected).  A trailing
``# numcheck: disable=N1`` comment on the flagged source line
suppresses a finding.  Everything is host-side tracing on the CPU
backend — nothing executes on a device.
"""

from .provenance import ProvReport, analyze_provenance
from .rules import check_rules
from .runner import (Violation, discover_contracts, run_contract,
                     run_contracts)

__all__ = ["ProvReport", "Violation", "analyze_provenance", "check_rules",
           "discover_contracts", "run_contract", "run_contracts"]
