"""racecheck: static concurrency, signal-safety, and buffer-lifetime
auditor for the serving runtime (docs/LINTING.md).

The third static layer next to jaxlint (AST JAX discipline) and
jaxprcheck (jaxpr/HLO contracts): whole-program invariants of the code
*around* the compiled sampler — the watchdog worker thread, the
preemption signal path, the donation protocol between scheduler and
jitted mux, and the job/breaker state machines.  Pure ``ast`` over
``runtime/``/``serve/``/``obs/``; the audited modules are never
imported, so the gate runs anywhere in milliseconds with zero device
(or even jax) involvement.

Rules: L1 unguarded-shared-write, L2 lock-order-hazard,
S1 signal-unsafe-call, C6 use-after-donate, M1 unknown-state,
M2 unreachable-state, M3 undeclared-transition.
Suppress a site with ``# racecheck: disable=<RULE>``; accept
pre-existing debt in ``racecheck_baseline.json`` — each baselined
(file, rule) pair must carry a one-line justification.
"""

from .model import RULES, Corpus, Finding, ModuleModel, build_corpus
from .runner import (analyze_repo, analyze_sources, check_justifications,
                     load_baseline_file, load_config, run_passes,
                     write_baseline_file)

__all__ = ["RULES", "Corpus", "Finding", "ModuleModel", "build_corpus",
           "analyze_repo", "analyze_sources", "check_justifications",
           "load_baseline_file", "load_config", "run_passes",
           "write_baseline_file"]
