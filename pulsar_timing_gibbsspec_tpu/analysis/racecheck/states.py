"""M1/M2/M3: declared state machines vs the actual transition sites.

The config (``contracts/racecheck.json``) declares each machine: its
states, initial state(s), and the legal transition table — the job
lifecycle (``queued -> warming -> sampling -> draining -> done/
failed/quarantined``) and the tenant circuit breaker (``closed/open/
half_open``).  The pass finds every literal transition *site* in the
machine's files:

- ``setter`` machines: ``recv.set_state("lit")`` calls;
- ``attr`` machines: ``recv.state = "lit"`` assigns (optionally
  restricted to one class, so ``CircuitBreaker.state`` does not absorb
  unrelated ``.state`` attributes).

and checks three things.  **M1**: a state literal (or a ``states_const``
tuple like ``serve/jobs.py:JOB_STATES``) outside the declared set — a
new state cannot land without updating the table.  **M2**: a declared
non-initial state with no site assigning it — dead lifecycle states
rot into lies.  **M3**: where a site's *source* state is statically
known, the edge must be declared.  Sources are inferred two ways, both
local and deliberately conservative: an earlier site on the same
receiver in the same straight-line suite (``set_state("warming") ...
set_state("sampling")``), or an enclosing ``if recv.state == "lit":``
guard.  Branch joins keep a source only when every surviving arm
agrees (a ``return`` arm drops out); loop bodies are walked once with
the loop target cleared, so per-iteration rebinding cannot fabricate a
cross-iteration edge.
"""

from __future__ import annotations

import ast

from .model import Corpus, Finding, qualname


def _literal_str(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class _Machine:
    def __init__(self, cfg: dict, config_path: str):
        self.name = cfg.get("name", "?")
        self.files = list(cfg.get("files", ()))
        self.setter = cfg.get("setter")
        self.attr = cfg.get("attr")
        self.klass = cfg.get("class")
        self.state_attr = cfg.get("state_attr", "state")
        self.states = set(cfg.get("states", ()))
        self.initial = set(cfg.get("initial", ()))
        self.transitions = {tuple(t) for t in cfg.get("transitions", ())}
        self.states_const = cfg.get("states_const")
        self.config_path = config_path


def _site_of(machine: _Machine, mod, stmt):
    """(receiver, dst, node) when ``stmt`` is a transition site."""
    if machine.setter is not None and isinstance(stmt, ast.Expr) and \
            isinstance(stmt.value, ast.Call):
        call = stmt.value
        if isinstance(call.func, ast.Attribute) and \
                call.func.attr == machine.setter and call.args:
            dst = _literal_str(call.args[0])
            if dst is not None:
                recv = qualname(call.func.value)
                return recv, dst, call
    if machine.attr is not None and isinstance(stmt, ast.Assign) and \
            len(stmt.targets) == 1:
        t = stmt.targets[0]
        if isinstance(t, ast.Attribute) and t.attr == machine.attr:
            dst = _literal_str(stmt.value)
            if dst is not None:
                if machine.klass is not None and \
                        mod.enclosing_class(stmt) != machine.klass:
                    return None
                recv = qualname(t.value)
                return recv, dst, stmt
    return None


def _guard_state(machine: _Machine, test):
    """(receiver, state) from an ``if recv.state == "lit":`` test."""
    if isinstance(test, ast.Compare) and len(test.ops) == 1 and \
            isinstance(test.ops[0], ast.Eq):
        left = qualname(test.left)
        lit = _literal_str(test.comparators[0])
        if left is not None and lit is not None and \
                left.endswith("." + machine.state_attr):
            recv = left[:-(len(machine.state_attr) + 1)]
            return recv, lit
    return None


def _terminates(stmts) -> bool:
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue))


def _merge(entry: dict, arms) -> dict:
    """Join of branch-local source maps: keep a receiver only when
    every surviving arm agrees on its state."""
    alive = [a for a, terminated in arms if not terminated]
    if not alive:
        return dict(entry)
    out = {}
    for recv, state in alive[0].items():
        if all(a.get(recv) == state for a in alive[1:]):
            out[recv] = state
    return out


class _MachineScan:
    def __init__(self, machine: _Machine, mod, report):
        self.m = machine
        self.mod = mod
        self.report = report
        self.seen_dsts: set = set()

    def _visit_site(self, site, last: dict):
        recv, dst, node = site
        self.seen_dsts.add(dst)
        if dst not in self.m.states:
            self.report(self.mod.path, node.lineno, "M1",
                        f"machine '{self.m.name}': state {dst!r} is not "
                        f"in the declared set {sorted(self.m.states)}")
            return
        src = last.get(recv) if recv is not None else None
        if src is not None and (src, dst) not in self.m.transitions:
            self.report(self.mod.path, node.lineno, "M3",
                        f"machine '{self.m.name}': transition "
                        f"{src!r} -> {dst!r} is not in the declared "
                        "table — declare it in contracts/racecheck.json "
                        "or fix the lifecycle")
        if recv is not None:
            last[recv] = dst

    def walk(self, stmts, last: dict):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.walk(stmt.body, {})
                continue
            if isinstance(stmt, ast.ClassDef):
                self.walk(stmt.body, {})
                continue
            site = _site_of(self.m, self.mod, stmt)
            if site is not None:
                self._visit_site(site, last)
                continue
            if isinstance(stmt, ast.If):
                entry = dict(last)
                a = dict(last)
                guard = _guard_state(self.m, stmt.test)
                if guard is not None and guard[1] in self.m.states:
                    a[guard[0]] = guard[1]
                b = dict(last)
                self.walk(stmt.body, a)
                self.walk(stmt.orelse, b)
                merged = _merge(entry, [
                    (a, _terminates(stmt.body)),
                    (b, _terminates(stmt.orelse) if stmt.orelse
                     else False)])
                last.clear()
                last.update(merged)
            elif isinstance(stmt, ast.For):
                body_entry = dict(last)
                for tok in _for_targets(stmt):
                    for k in [k for k in body_entry
                              if k == tok or k.startswith(tok + ".")]:
                        del body_entry[k]
                a = dict(body_entry)
                self.walk(stmt.body, a)
                self.walk(stmt.orelse, a)
                last.clear()
                last.update({k: v for k, v in body_entry.items()
                             if a.get(k) == v})
            elif isinstance(stmt, ast.While):
                entry = dict(last)
                a = dict(last)
                self.walk(stmt.body, a)
                self.walk(stmt.orelse, a)
                last.clear()
                last.update({k: v for k, v in entry.items()
                             if a.get(k) == v})
            elif isinstance(stmt, ast.With):
                self.walk(stmt.body, last)
            elif isinstance(stmt, ast.Try):
                a = dict(last)
                self.walk(stmt.body, a)
                self.walk(stmt.orelse, a)
                arms = [(a, _terminates(stmt.body))]
                for h in stmt.handlers:
                    b = dict(last)
                    self.walk(h.body, b)
                    arms.append((b, _terminates(h.body)))
                merged = _merge(last, arms)
                last.clear()
                last.update(merged)
                self.walk(stmt.finalbody, last)


def _for_targets(stmt: ast.For):
    def flat(t):
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                yield from flat(e)
        else:
            q = qualname(t)
            if q is not None:
                yield q
    return list(flat(stmt.target))


def _check_states_const(machine: _Machine, corpus: Corpus, report):
    spec = machine.states_const
    mod = corpus.by_path.get(spec["file"])
    if mod is None:
        report(spec["file"], 0, "M1",
               f"machine '{machine.name}': states_const file "
               f"{spec['file']!r} is not in the analyzed corpus")
        return
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name) and \
                stmt.targets[0].id == spec["name"]:
            if isinstance(stmt.value, (ast.Tuple, ast.List)):
                got = {e.value for e in stmt.value.elts
                       if isinstance(e, ast.Constant)
                       and isinstance(e.value, str)}
                if got != machine.states:
                    report(mod.path, stmt.lineno, "M1",
                           f"machine '{machine.name}': {spec['name']} = "
                           f"{sorted(got)} does not match the declared "
                           f"states {sorted(machine.states)} — update "
                           "both together")
                return
    report(mod.path, 0, "M1",
           f"machine '{machine.name}': states_const {spec['name']!r} "
           f"not found at module level of {spec['file']}")


def check_states(corpus: Corpus, config: dict | None = None,
                 config_path: str = "contracts/racecheck.json") -> list:
    """All M1/M2/M3 findings for the configured machines."""
    findings: list = []

    def report(path, line, rule, msg):
        findings.append(Finding(path, line, rule, msg))

    for cfg in (config or {}).get("machines", ()):
        machine = _Machine(cfg, config_path)
        if machine.files and \
                not any(p in corpus.by_path for p in machine.files):
            # subset run (explicit paths on the CLI): none of this
            # machine's files are in scope, so there is no evidence to
            # audit — skip rather than report every state unreachable
            continue
        for src, dst in sorted(machine.transitions):
            for s in (src, dst):
                if s not in machine.states:
                    report(machine.files[0] if machine.files
                           else config_path, 0, "M1",
                           f"machine '{machine.name}': declared "
                           f"transition references unknown state {s!r}")
        if machine.states_const:
            _check_states_const(machine, corpus, report)
        seen: set = set()
        for path in machine.files:
            mod = corpus.by_path.get(path)
            if mod is None:
                continue
            scan = _MachineScan(machine, mod, report)
            scan.walk(mod.tree.body, {})
            seen |= scan.seen_dsts
        for state in sorted(machine.states - machine.initial - seen):
            report(machine.files[0] if machine.files else config_path,
                   0, "M2",
                   f"machine '{machine.name}': declared state {state!r} "
                   "has no transition site in "
                   f"{machine.files or '(no files)'} — unreachable "
                   "(remove it from the table or wire the transition)")
    return findings
