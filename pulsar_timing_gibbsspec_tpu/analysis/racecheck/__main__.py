"""CLI: audit the runtime/serving layers, ratchet against the baseline.

Usage::

    python -m pulsar_timing_gibbsspec_tpu.analysis.racecheck [paths...]

    --config PATH      contracts-style config (default
                       <repo>/contracts/racecheck.json)
    --json             machine-readable findings on stdout
    --baseline PATH    ratchet file (default <repo>/racecheck_baseline.json)
    --no-baseline      report every finding, ignore the ratchet
    --write-baseline   accept current findings as the new baseline
                       (existing justifications kept; new pairs get a
                       TODO stub the gate rejects until filled in)

Exit status 1 when findings beyond the baseline exist, when a stale
baseline entry should be ratcheted down is *not* an error (reported),
and when any baselined pair lacks a one-line justification.  Pure AST
analysis: the audited modules are parsed, never imported — no jax, no
threads, no signal handlers, no device.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .runner import (_REPO_ROOT, analyze_repo, check_justifications,
                     load_baseline_file, load_config, write_baseline_file)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="racecheck",
        description="static concurrency / signal-safety / buffer-lifetime "
                    "auditor for the serving runtime (AST only, no import)")
    ap.add_argument("paths", nargs="*",
                    help="files or directories (default: config paths)")
    ap.add_argument("--config", default=None, metavar="PATH")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--baseline",
                    default=str(_REPO_ROOT / "racecheck_baseline.json"))
    ap.add_argument("--no-baseline", action="store_true")
    ap.add_argument("--write-baseline", action="store_true")
    args = ap.parse_args(argv)

    config = load_config(args.config)
    findings, analyzed = analyze_repo(args.paths or None, config)

    if args.write_baseline:
        data = write_baseline_file(args.baseline, findings, _REPO_ROOT)
        todo = check_justifications(data)
        print(f"racecheck: baseline written to {args.baseline} "
              f"({len(findings)} finding(s), {len(todo)} justification(s) "
              "to fill in)")
        return 0

    from ..baseline import compare_to_baseline

    if args.no_baseline:
        new, stale, missing = list(findings), [], []
    else:
        data = load_baseline_file(args.baseline)
        new, stale = compare_to_baseline(findings, data["violations"],
                                         _REPO_ROOT, set(analyzed))
        missing = check_justifications(data)

    if args.as_json:
        print(json.dumps(
            {"analyzed": analyzed,
             "findings": [{"path": f.path, "line": f.line,
                           "rule": f.rule, "msg": f.msg}
                          for f in findings],
             "new": len(new),
             "missing_justifications": [list(m) for m in missing]},
            indent=2, sort_keys=True))
    else:
        for f in new:
            print(str(f))
        for f, rule, base, cur in stale:
            print(f"stale baseline entry: {f} [{rule}] baseline {base} "
                  f"> current {cur}; ratchet the baseline down")
        for f, rule in missing:
            print(f"baselined without justification: {f} [{rule}] — add "
                  f"a one-line reason under justifications in "
                  f"{Path(args.baseline).name}")
        ok = "OK" if not new and not missing else "FAIL"
        print(f"racecheck: {len(analyzed)} file(s), {len(findings)} "
              f"finding(s), {len(new)} new — {ok}")
    return 1 if (new or missing) else 0


if __name__ == "__main__":
    sys.exit(main())
