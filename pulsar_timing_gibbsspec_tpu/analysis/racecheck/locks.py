"""L1/L2: lock discipline over the module-global registries.

**L1 — unguarded shared write.**  Every *write* (rebinding through a
``global`` declaration, subscript store/delete, in-place mutator call)
to a shared mutable module global must happen lexically inside a
``with <module-lock>:`` block.  The runtime registries are touched
from the watchdog worker thread (trace spans run inside the dispatch
closure), the signal path, and the between-chunk scheduler, so an
unguarded ``_tids[ident] = ...`` is a real torn-dict hazard, not
style.  Unguarded *reads* are deliberately out of scope: under
CPython's GIL a single reference load is atomic, and the hot paths
(``drain_requested``, the span fast path) rely on exactly that —
flagging them would bury the signal.

**L2 — lock-order hazard.**  A graph of "acquired B while holding A"
edges, built per function and propagated through corpus-resolvable
calls (so ``with a_lock: helper()`` where ``helper`` takes ``b_lock``
contributes the A->B edge).  A cycle in the graph is a potential
deadlock; re-acquiring a held non-reentrant ``Lock`` (directly or via
a call chain) is the degenerate self-cycle and is flagged at the site.
"""

from __future__ import annotations

import ast

from .model import (MUTATORS, Corpus, Finding, ModuleModel, qualname,
                    walk_excluding_defs)


def _local_binds(fn) -> set:
    """Names bound in ``fn``'s own scope (parameters, assignments, loop
    targets, with-as, except-as, nested def/class names) — they shadow
    same-named module globals, so writes through them are not shared
    state."""
    out: set = set()
    a = fn.args
    for arg in [*a.posonlyargs, *a.args, *a.kwonlyargs]:
        out.add(arg.arg)
    if a.vararg is not None:
        out.add(a.vararg.arg)
    if a.kwarg is not None:
        out.add(a.kwarg.arg)

    def names_of(t):
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                yield from names_of(e)
        elif isinstance(t, ast.Name):
            yield t.id

    for node in walk_excluding_defs(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            out.add(node.name)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                out.update(names_of(t))
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            out.update(names_of(node.target))
        elif isinstance(node, ast.For):
            out.update(names_of(node.target))
        elif isinstance(node, ast.withitem):
            if node.optional_vars is not None:
                out.update(names_of(node.optional_vars))
        elif isinstance(node, ast.ExceptHandler):
            if node.name:
                out.add(node.name)
    return out


def _held_locks(mod: ModuleModel, items) -> set:
    """Module-lock names acquired by a ``with`` statement's items."""
    got = set()
    for it in items:
        q = qualname(it.context_expr)
        if q in mod.locks:
            got.add(q)
    return got


def _expr_mutations(mod: ModuleModel, node, shadowed=frozenset()):
    """(name, node) for in-place mutator calls on shared globals inside
    an expression tree (nested defs excluded — defining is not calling)."""
    for cur in walk_excluding_defs(node):
        if not isinstance(cur, ast.Call) or \
                not isinstance(cur.func, ast.Attribute):
            continue
        base = cur.func.value
        if isinstance(base, ast.Name) and base.id in mod.shared \
                and base.id not in shadowed and cur.func.attr in MUTATORS:
            yield base.id, cur


def _stmt_writes(mod: ModuleModel, stmt, global_decls: set,
                 shadowed=frozenset()):
    """(name, node) writes to shared globals in one simple statement."""
    yield from _expr_mutations(mod, stmt, shadowed)

    def targets_of(t):
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                yield from targets_of(e)
        else:
            yield t

    tgts = []
    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            tgts.extend(targets_of(t))
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        tgts.extend(targets_of(stmt.target))
    elif isinstance(stmt, ast.Delete):
        for t in stmt.targets:
            tgts.extend(targets_of(t))
    for t in tgts:
        if isinstance(t, ast.Name):
            if t.id in mod.shared and t.id in global_decls:
                yield t.id, t
        elif isinstance(t, (ast.Subscript, ast.Attribute)):
            base = t.value
            if isinstance(base, ast.Name) and base.id in mod.shared \
                    and base.id not in shadowed:
                yield base.id, t


class _FnLockScan:
    """One function scope: L1 sites, local acquisitions, call sites
    annotated with the locks held around them."""

    def __init__(self, mod: ModuleModel, corpus: Corpus, fn):
        self.mod = mod
        self.corpus = corpus
        self.fn = fn
        self.global_decls = mod.global_names(fn)
        self.shadowed = _local_binds(fn) - self.global_decls
        self.l1: list = []           # (name, node)
        self.acquires: set = set()   # lock ids ever taken in this scope
        self.order_edges: list = []  # (held_id, taken_id, node)
        self.self_reacquire: list = []     # (lock_id, node)
        self.calls: list = []        # (resolved, frozenset(held_ids), node)

    def _lock_id(self, name: str) -> str:
        return f"{self.mod.modname}.{name}"

    def run(self):
        self._walk(self.fn.body, frozenset())
        return self

    def _scan_expr(self, node, held):
        for name, site in _expr_mutations(self.mod, node, self.shadowed):
            if not held:
                self.l1.append((name, site))
        self._scan_calls(node, held)

    def _scan_calls(self, node, held):
        for cur in walk_excluding_defs(node):
            if not isinstance(cur, ast.Call):
                continue
            # bare ``X.acquire()`` on a module lock counts as taking it
            # (scope-less: it feeds the transitive summary, not ``held``)
            if isinstance(cur.func, ast.Attribute) and \
                    cur.func.attr == "acquire" and \
                    isinstance(cur.func.value, ast.Name) and \
                    cur.func.value.id in self.mod.locks:
                lid = self._lock_id(cur.func.value.id)
                for h in held:
                    if h == lid:
                        self.self_reacquire.append((lid, cur))
                    else:
                        self.order_edges.append((h, lid, cur))
                self.acquires.add(lid)
                continue
            res = self.corpus.resolve_call(self.mod, cur)
            if res[0] == "func":
                self.calls.append((res, held, cur))

    def _walk(self, stmts, held):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.With):
                new = _held_locks(self.mod, stmt.items)
                for it in stmt.items:
                    self._scan_expr(it.context_expr, held)
                new_ids = set()
                for name in new:
                    lid = self._lock_id(name)
                    if lid in held and self.mod.locks[name] == "Lock":
                        self.self_reacquire.append((lid, stmt))
                    for h in held:
                        if h != lid:
                            self.order_edges.append((h, lid, stmt))
                    new_ids.add(lid)
                self.acquires.update(new_ids)
                self._walk(stmt.body, held | frozenset(new_ids))
            elif isinstance(stmt, ast.If):
                self._scan_expr(stmt.test, held)
                self._walk(stmt.body, held)
                self._walk(stmt.orelse, held)
            elif isinstance(stmt, ast.While):
                self._scan_expr(stmt.test, held)
                self._walk(stmt.body, held)
                self._walk(stmt.orelse, held)
            elif isinstance(stmt, ast.For):
                self._scan_expr(stmt.iter, held)
                self._walk(stmt.body, held)
                self._walk(stmt.orelse, held)
            elif isinstance(stmt, ast.Try):
                self._walk(stmt.body, held)
                for h in stmt.handlers:
                    self._walk(h.body, held)
                self._walk(stmt.orelse, held)
                self._walk(stmt.finalbody, held)
            else:
                for name, site in _stmt_writes(self.mod, stmt,
                                               self.global_decls,
                                               self.shadowed):
                    if not held:
                        self.l1.append((name, site))
                self._scan_calls(stmt, held)


def check_locks(corpus: Corpus) -> list:
    """All L1/L2 findings over the corpus."""
    findings: list = []
    lock_kinds: dict = {}
    for mod in corpus.modules.values():
        for name, kind in mod.locks.items():
            lock_kinds[f"{mod.modname}.{name}"] = kind

    scans: dict = {}
    for mod in corpus.modules.values():
        for fn in mod.all_defs:
            scans[id(fn)] = _FnLockScan(mod, corpus, fn).run()

    # L1
    for scan in scans.values():
        for name, site in scan.l1:
            mod = scan.mod
            avail = ", ".join(sorted(mod.locks)) or "none defined"
            findings.append(Finding(
                mod.path, getattr(site, "lineno", 0), "L1",
                f"write to shared module global '{name}' outside any "
                f"module-lock 'with' block (module locks: {avail}); "
                "the registry is reachable from the watchdog worker "
                "thread / signal path"))

    # transitive acquire summaries (fixpoint over the call graph)
    trans = {k: set(s.acquires) for k, s in scans.items()}
    changed = True
    while changed:
        changed = False
        for k, scan in scans.items():
            for (res, _held, _node) in scan.calls:
                callee = id(res[2])
                if callee in trans and not trans[callee] <= trans[k]:
                    trans[k] |= trans[callee]
                    changed = True

    # L2 self-reacquire: direct, and through a call chain
    edges: dict = {}   # (a, b) -> (path, line)
    for scan in scans.values():
        for lid, node in scan.self_reacquire:
            findings.append(Finding(
                scan.mod.path, getattr(node, "lineno", 0), "L2",
                f"re-acquisition of non-reentrant lock '{lid}' while "
                "already held — self-deadlock"))
        for (a, b, node) in scan.order_edges:
            edges.setdefault((a, b),
                             (scan.mod.path, getattr(node, "lineno", 0)))
        for (res, held, node) in scan.calls:
            callee_locks = trans.get(id(res[2]), set())
            for h in held:
                for t in callee_locks:
                    if t == h:
                        if lock_kinds.get(h) == "Lock":
                            findings.append(Finding(
                                scan.mod.path, getattr(node, "lineno", 0),
                                "L2",
                                f"call to '{res[3]}' can re-acquire "
                                f"non-reentrant lock '{h}' already held "
                                "here — self-deadlock"))
                    else:
                        edges.setdefault(
                            (h, t),
                            (scan.mod.path, getattr(node, "lineno", 0)))

    # L2 cycles in the acquired-while-holding graph
    graph: dict = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    seen_cycles: set = set()
    for start in sorted(graph):
        stack = [(start, (start,))]
        while stack:
            cur, path = stack.pop()
            for nxt in graph.get(cur, ()):
                if nxt == start:
                    cyc = frozenset(path)
                    if cyc in seen_cycles:
                        continue
                    seen_cycles.add(cyc)
                    p, line = edges[(cur, start)]
                    order = " -> ".join(path + (start,))
                    findings.append(Finding(
                        p, line, "L2",
                        f"lock-order cycle: {order} — two threads taking "
                        "these locks in opposite orders can deadlock"))
                elif nxt not in path:
                    stack.append((nxt, path + (nxt,)))
    return findings
