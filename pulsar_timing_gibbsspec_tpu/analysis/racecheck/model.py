"""The shared program model racecheck's passes walk.

Everything here is plain :mod:`ast` — the checked modules are parsed,
never imported, so auditing ``runtime/``/``serve/`` cannot initialize
jax, spin up the watchdog thread, or install signal handlers as a side
effect.  One :class:`Corpus` holds a :class:`ModuleModel` per file and
resolves cross-module calls through each module's import alias map
(relative imports included — the runtime layers import each other as
``from . import telemetry`` / ``from ..obs import trace as otrace``),
which is what lets the signal pass follow the handler into
``telemetry.incr`` and the lock pass summarize callee acquisitions
across files.

Per module the model records the concurrency-relevant surface:

- **locks** — module-level ``X = threading.Lock()`` / ``RLock()``
  assigns (the repo convention for registry guards), with their kind:
  the signal pass treats ``RLock`` acquisition as reentrancy-safe and
  plain ``Lock`` as a self-deadlock hazard;
- **shared mutable globals** — module-level dict/list/set/deque
  displays or constructor calls, plus any name a function rebinds
  through a ``global`` declaration (the ``_enabled``/``_dropped``
  scalar flags);
- **functions** — every def (nested included) with parent links, so
  handlers registered as closures (``preemption.install``'s
  ``_handler``) are first-class call-graph roots.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path

RULES = {
    "L1": "unguarded-shared-write",
    "L2": "lock-order-hazard",
    "S1": "signal-unsafe-call",
    "C6": "use-after-donate",
    "M1": "unknown-state",
    "M2": "unreachable-state",
    "M3": "undeclared-transition",
}

_PRAGMA_RE = re.compile(r"#\s*racecheck:\s*disable=([A-Za-z0-9,\s]+)")

#: constructor calls whose module-level result is shared mutable state
_MUTABLE_CTORS = {
    "dict", "list", "set", "collections.deque", "collections.OrderedDict",
    "collections.defaultdict", "collections.Counter", "deque",
    "OrderedDict", "defaultdict", "Counter",
}

#: method names that mutate their receiver in place
MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "update", "setdefault", "pop", "popleft", "popitem", "remove",
    "discard", "clear", "sort", "reverse",
}


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    line: int
    rule: str
    msg: str

    def __str__(self):
        return (f"{self.path}:{self.line}: {self.rule} "
                f"[{RULES[self.rule]}] {self.msg}")


def pragma_rules(line: str) -> set:
    """Rules a trailing ``# racecheck: disable=...`` comment suppresses."""
    m = _PRAGMA_RE.search(line)
    if not m:
        return set()
    return {r.strip().upper() for r in m.group(1).split(",") if r.strip()}


def qualname(node):
    """Dotted display name of a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _modname_for(relpath: str) -> str:
    """Dotted module name of a repo-relative posix path."""
    p = relpath[:-3] if relpath.endswith(".py") else relpath
    parts = [s for s in p.split("/") if s]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class ModuleModel:
    """One parsed module and its concurrency-relevant surface."""

    def __init__(self, src: str, path: str, modname: str | None = None):
        self.path = path
        self.modname = modname if modname is not None else _modname_for(path)
        self.package = self.modname.rsplit(".", 1)[0] \
            if "." in self.modname else ""
        self.src_lines = src.splitlines()
        self.tree = ast.parse(src, filename=path)
        self.parents: dict = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.aliases = self._collect_aliases()
        self.locks = self._collect_locks()
        self.shared = self._collect_shared()
        self.functions: dict[str, ast.FunctionDef] = {}
        self.all_defs: list = []
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.all_defs.append(node)
                # module-level functions are call-resolution targets;
                # methods/nested defs stay reachable via all_defs
                if isinstance(self.parents.get(node), ast.Module):
                    self.functions[node.name] = node

    # -- imports ------------------------------------------------------------

    def _collect_aliases(self) -> dict:
        """name -> absolute dotted target, relative imports resolved
        against this module's package (function-local imports included —
        the runtime layers import jax lazily)."""
        out: dict = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    out[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    pkg = self.modname.split(".")
                    # level 1 = this package, 2 = its parent, ...
                    pkg = pkg[:len(pkg) - node.level]
                    base = ".".join(pkg + ([base] if base else []))
                for a in node.names:
                    if a.name == "*":
                        continue
                    tgt = f"{base}.{a.name}" if base else a.name
                    out[a.asname or a.name] = tgt
        return out

    def expand(self, dotted: str | None) -> str | None:
        """Alias-expand the head of a dotted display name
        (``otrace.instant`` -> ``pkg.obs.trace.instant``)."""
        if dotted is None:
            return None
        head, _, rest = dotted.partition(".")
        tgt = self.aliases.get(head)
        if tgt is None:
            return dotted
        return f"{tgt}.{rest}" if rest else tgt

    # -- module-level concurrency surface -----------------------------------

    def _collect_locks(self) -> dict:
        """Module-level ``X = threading.Lock()/RLock()`` -> kind."""
        out: dict = {}
        for node in self.tree.body:
            if not isinstance(node, ast.Assign) or \
                    not isinstance(node.value, ast.Call):
                continue
            callee = self.expand(qualname(node.value.func))
            if callee not in ("threading.Lock", "threading.RLock",
                              "Lock", "RLock"):
                continue
            kind = "RLock" if callee.endswith("RLock") else "Lock"
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = kind
        return out

    def _collect_shared(self) -> dict:
        """Shared mutable module globals: name -> defining line."""
        out: dict = {}
        for node in self.tree.body:
            targets, value = [], None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            if not targets:
                continue
            mutable = isinstance(value, (ast.Dict, ast.List, ast.Set))
            if isinstance(value, ast.Call):
                callee = self.expand(qualname(value.func))
                mutable = callee in _MUTABLE_CTORS
            if not mutable:
                continue
            for t in targets:
                if isinstance(t, ast.Name) and t.id not in self.locks:
                    out[t.id] = node.lineno
        # names rebound through ``global`` are shared process state even
        # when scalar (flags, counters, the sink reference)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Global):
                for name in node.names:
                    if name not in self.locks:
                        out.setdefault(name, node.lineno)
        return out

    def global_names(self, fn) -> set:
        """Names ``fn`` declares ``global`` (its own body only)."""
        out: set = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                continue
            if isinstance(node, ast.Global):
                out.update(node.names)
        return out

    def enclosing_class(self, node):
        """Nearest enclosing ClassDef name, or None."""
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur.name
            cur = self.parents.get(cur)
        return None

    def line(self, lineno: int) -> str:
        if 0 < lineno <= len(self.src_lines):
            return self.src_lines[lineno - 1]
        return ""


def body_statements(fn) -> list:
    """The statement list of a def (excluding nested defs' bodies is the
    walker's job; this is just the top-level list)."""
    return list(fn.body)


def walk_excluding_defs(node):
    """``ast.walk`` over a function body that does not descend into
    nested function/class definitions (defining is not calling)."""
    stack = list(ast.iter_child_nodes(node)) if isinstance(
        node, (ast.FunctionDef, ast.AsyncFunctionDef)) else [node]
    while stack:
        cur = stack.pop()
        yield cur
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(cur))


class Corpus:
    """All analyzed modules, indexed for cross-module call resolution."""

    def __init__(self, modules: list):
        self.modules = {m.modname: m for m in modules}
        self.by_path = {m.path: m for m in modules}

    def resolve_call(self, mod: ModuleModel, call: ast.Call):
        """Resolve a call site to a corpus function when possible.

        Returns ``("func", module, fndef, display)`` for a function
        defined in the corpus, ``("external", dotted, None, display)``
        for an alias-expanded external dotted name, or
        ``("opaque", None, None, display)`` when the callee cannot be
        named statically (method on a runtime object, subscript, ...).
        """
        display = qualname(call.func)
        if display is None:
            return ("opaque", None, None, None)
        # bare local function name
        if "." not in display and display in mod.functions:
            return ("func", mod, mod.functions[display], display)
        expanded = mod.expand(display)
        # alias to another corpus module's function:
        #   from . import telemetry; telemetry.incr(...)
        #   from .telemetry import incr; incr(...)
        if "." in expanded:
            owner, _, fname = expanded.rpartition(".")
            target = self.modules.get(owner)
            if target is not None and fname in target.functions:
                return ("func", target, target.functions[fname], display)
        if expanded != display or "." in display:
            return ("external", expanded, None, display)
        return ("external", display, None, display)


def build_corpus(sources: dict) -> Corpus:
    """Corpus from ``{repo-relative-path: source}`` (test entry point)."""
    return Corpus([ModuleModel(src, path) for path, src in sources.items()])


def iter_py_files(paths):
    for p in paths:
        p = Path(p)
        if p.is_dir():
            yield from sorted(p.rglob("*.py"))
        elif p.suffix == ".py":
            yield p


def load_corpus(paths, root: Path) -> Corpus:
    """Corpus over the .py files under ``paths``; module names derive
    from the path relative to the repo ``root``."""
    mods = []
    for f in iter_py_files(paths):
        try:
            rel = f.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = f.as_posix()
        mods.append(ModuleModel(f.read_text(), rel))
    return Corpus(mods)
