"""C6: use-after-donate — the host side of ``donate_argnums``.

``jax.jit(fn, donate_argnums=...)`` invalidates the donated argument
buffers the moment the call dispatches: the runtime may alias the
output into the donated storage, and on the CPU backend the "buffer"
is host heap — touching the stale reference afterwards is exactly the
PR 13 corruption (intermittent segfaults in the chunk dispatch once
the service re-read a donated mux carry).  jaxprcheck's ``donation``
check proves the *device* side (outputs actually alias); this pass
proves the *host* side: after a donating call, every donated argument
name must be re-bound from the call's outputs (``x, b = mux(s, x, b,
...)``) or never read again — a later read of the stale name is a C6
finding.

Donating callables are discovered three ways, all static:

1. a direct binding ``mux = jax.jit(body, donate_argnums=(1, 2))``;
2. a *factory* — a function whose ``return`` is such a jit call
   (``serve/engine.make_mux``) — makes every ``g = make_mux(n)``
   binding a donating callable with the same positions (positions are
   the union over the factory's returns: a branch that disables
   donation on one backend does not make the host pattern safe on the
   others);
3. an immediately-invoked ``jax.jit(..., donate_argnums=...)(args)``.

The walk is branch-aware: ``if``/``try`` arms run on copies of the
liveness state and a name dead in any surviving arm stays dead at the
join; a ``return``/``raise`` arm drops out of the join.  Re-binding
(any assignment to the name, including attribute targets) revives it.
"""

from __future__ import annotations

import ast

from .model import Corpus, Finding, ModuleModel, qualname

_JIT_NAMES = {"jax.jit", "jit"}


def _int_elems(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
        return out
    return []


def _str_elems(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return []


def _jit_donation(mod: ModuleModel, call: ast.Call):
    """``(argnums, argnames)`` of a donating jit call, else None."""
    if not isinstance(call, ast.Call):
        return None
    if mod.expand(qualname(call.func)) not in _JIT_NAMES:
        return None
    nums, names = set(), set()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            nums.update(_int_elems(kw.value))
        elif kw.arg == "donate_argnames":
            names.update(_str_elems(kw.value))
    if not nums and not names:
        return None
    return frozenset(nums), frozenset(names)


def _collect_factories(corpus: Corpus) -> dict:
    """id(fndef) -> (argnums, argnames) for functions returning a
    donating jit call (union over all returns)."""
    out: dict = {}
    for mod in corpus.modules.values():
        for fn in mod.all_defs:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            nums, names = set(), set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Return) and node.value is not None:
                    got = _jit_donation(mod, node.value) \
                        if isinstance(node.value, ast.Call) else None
                    if got:
                        nums |= got[0]
                        names |= got[1]
            if nums or names:
                out[id(fn)] = (frozenset(nums), frozenset(names))
    return out


def _terminates(stmts) -> bool:
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue))


def _assign_targets(stmt):
    """Flat token list of assignment-target names/attribute chains."""
    def flat(t):
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                yield from flat(e)
        else:
            q = qualname(t)
            if q is not None:
                yield q

    out = []
    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            out.extend(flat(t))
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        out.extend(flat(stmt.target))
    elif isinstance(stmt, ast.Delete):
        for t in stmt.targets:
            out.extend(flat(t))
    elif isinstance(stmt, ast.For):
        out.extend(flat(stmt.target))
    elif isinstance(stmt, ast.With):
        for it in stmt.items:
            if it.optional_vars is not None:
                out.extend(flat(it.optional_vars))
    return out


class _Liveness:
    __slots__ = ("donors", "dead")

    def __init__(self, donors=None, dead=None):
        #: callable token -> (argnums, argnames)
        self.donors: dict = dict(donors or {})
        #: donated token -> (line, callee display)
        self.dead: dict = dict(dead or {})

    def copy(self):
        return _Liveness(self.donors, self.dead)


class _FnDonateScan:
    def __init__(self, mod: ModuleModel, corpus: Corpus, factories: dict,
                 findings: list):
        self.mod = mod
        self.corpus = corpus
        self.factories = factories
        self.findings = findings

    # -- expression-level helpers -------------------------------------------

    def _walk_exprs(self, node):
        """Expression-tree walk that skips nested defs/lambdas."""
        stack = [node]
        while stack:
            cur = stack.pop()
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda, ast.ClassDef)):
                continue
            yield cur
            stack.extend(ast.iter_child_nodes(cur))

    def _check_reads(self, node, st: _Liveness):
        if not st.dead:
            return
        for cur in self._walk_exprs(node):
            if not isinstance(cur, (ast.Name, ast.Attribute)):
                continue
            if not isinstance(getattr(cur, "ctx", None), ast.Load):
                continue
            q = qualname(cur)
            if q in st.dead:
                line, callee = st.dead.pop(q)
                self.findings.append(Finding(
                    self.mod.path, cur.lineno, "C6",
                    f"'{q}' is read after being donated to '{callee}' "
                    f"(line {line}): the buffer may already be aliased "
                    "by the call's outputs — re-bind the name from the "
                    "results or copy before the donating call"))

    def _donation_of(self, call: ast.Call, st: _Liveness):
        """(argnums, argnames) when ``call`` donates, else None."""
        direct = _jit_donation(self.mod, call)
        if direct:
            return direct
        tok = qualname(call.func)
        if tok in st.donors:
            return st.donors[tok]
        # immediately-invoked jitted callable: jax.jit(f, donate...)(x)
        if isinstance(call.func, ast.Call):
            return _jit_donation(self.mod, call.func)
        return None

    def _kills(self, node, st: _Liveness):
        """Tokens a statement's donating calls invalidate."""
        killed: dict = {}
        for cur in self._walk_exprs(node):
            if not isinstance(cur, ast.Call):
                continue
            got = self._donation_of(cur, st)
            if not got:
                continue
            nums, names = got
            callee = qualname(cur.func) or "<jit>"
            for i in nums:
                if i < len(cur.args):
                    q = qualname(cur.args[i])
                    if q is not None:
                        killed[q] = (cur.lineno, callee)
            for kw in cur.keywords:
                if kw.arg in names:
                    q = qualname(kw.value)
                    if q is not None:
                        killed[q] = (cur.lineno, callee)
        return killed

    def _donor_from_value(self, value, st: _Liveness):
        """Donation spec when ``value`` evaluates to a donating
        callable (a donating jit call, or a factory call)."""
        if not isinstance(value, ast.Call):
            return None
        got = _jit_donation(self.mod, value)
        if got:
            return got
        res = self.corpus.resolve_call(self.mod, value)
        if res[0] == "func" and id(res[2]) in self.factories:
            return self.factories[id(res[2])]
        return None

    # -- statement walk -----------------------------------------------------

    def walk(self, stmts, st: _Liveness):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.If):
                self._simple(stmt.test, st)
                a, b = st.copy(), st.copy()
                self.walk(stmt.body, a)
                self.walk(stmt.orelse, b)
                self._merge(st, [(a, _terminates(stmt.body))],
                            [(b, _terminates(stmt.orelse)
                              if stmt.orelse else False)])
            elif isinstance(stmt, (ast.For, ast.While)):
                header = stmt.iter if isinstance(stmt, ast.For) \
                    else stmt.test
                self._simple(header, st)
                for tok in _assign_targets(stmt):
                    st.dead.pop(tok, None)
                a = st.copy()
                self.walk(stmt.body, a)
                self.walk(stmt.orelse, a)
                self._merge(st, [(a, False)], [])
            elif isinstance(stmt, ast.With):
                for it in stmt.items:
                    self._simple(it.context_expr, st)
                for tok in _assign_targets(stmt):
                    st.dead.pop(tok, None)
                self.walk(stmt.body, st)
            elif isinstance(stmt, ast.Try):
                arms = []
                a = st.copy()
                self.walk(stmt.body, a)
                self.walk(stmt.orelse, a)
                arms.append((a, _terminates(stmt.body + stmt.orelse)))
                for h in stmt.handlers:
                    b = st.copy()
                    self.walk(h.body, b)
                    arms.append((b, _terminates(h.body)))
                self._merge(st, arms, [])
                self.walk(stmt.finalbody, st)
            else:
                self._statement(stmt, st)

    def _merge(self, st: _Liveness, arms_a, arms_b):
        """Join: dead in any surviving arm stays dead; donors union."""
        st.dead.clear()
        st.donors.clear()
        for arm, terminated in arms_a + arms_b:
            if terminated:
                continue
            for k, v in arm.dead.items():
                st.dead.setdefault(k, v)
            for k, v in arm.donors.items():
                st.donors.setdefault(k, v)

    def _simple(self, node, st: _Liveness):
        """Reads-then-kills over one expression (no revival targets)."""
        self._check_reads(node, st)
        for tok, info in self._kills(node, st).items():
            st.dead[tok] = info

    def _statement(self, stmt, st: _Liveness):
        self._check_reads(stmt, st)
        killed = self._kills(stmt, st)
        targets = set(_assign_targets(stmt))
        for tok in targets:
            st.dead.pop(tok, None)
            st.donors.pop(tok, None)
        for tok, info in killed.items():
            if tok not in targets:
                st.dead[tok] = info
        # new donor bindings: mux = jax.jit(...donate...) / make_mux(n)
        value = getattr(stmt, "value", None)
        if isinstance(stmt, ast.Assign) and value is not None:
            spec = self._donor_from_value(value, st)
            if spec is not None:
                for tok in targets:
                    st.donors[tok] = spec


def check_donate(corpus: Corpus) -> list:
    """All C6 findings over the corpus."""
    findings: list = []
    factories = _collect_factories(corpus)
    for mod in corpus.modules.values():
        # module-level donors (mux = jax.jit(..., donate_argnums=...))
        seed = _Liveness()
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Assign) and \
                    isinstance(stmt.value, ast.Call):
                spec = _jit_donation(mod, stmt.value)
                if spec is None:
                    res = corpus.resolve_call(mod, stmt.value)
                    if res[0] == "func" and id(res[2]) in factories:
                        spec = factories[id(res[2])]
                if spec is not None:
                    for t in stmt.targets:
                        q = qualname(t)
                        if q is not None:
                            seed.donors[q] = spec
        scan = _FnDonateScan(mod, corpus, factories, findings)
        # module body (scripts/fixtures) and every function body
        st = _Liveness(seed.donors)
        scan.walk([s for s in mod.tree.body
                   if not isinstance(s, (ast.FunctionDef,
                                         ast.AsyncFunctionDef,
                                         ast.ClassDef))], st)
        for fn in mod.all_defs:
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan.walk(fn.body, _Liveness(seed.donors))
    return findings
