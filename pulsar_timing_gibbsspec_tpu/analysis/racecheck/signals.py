"""S1: the signal-handler call graph must stay async-signal-safe.

CPython delivers signals on the *main thread between bytecodes* — the
handler can preempt any point of the interpreter loop, including the
middle of a ``with _lock:`` block the main thread itself holds.  Three
thing are therefore banned anywhere reachable from a handler
registered via ``signal.signal(sig, fn)``:

1. acquiring a non-reentrant ``threading.Lock`` (``with`` or
   ``.acquire()``): if the interrupted frame holds that lock the
   handler deadlocks the process.  ``RLock`` acquisition is exempt —
   reentry succeeds by construction (the cost is bounded: at worst a
   racy registry update the owner re-does, never a wedge);
2. any call whose alias-expanded dotted name matches a *banned prefix*
   (``jax.`` dispatch, allocation-heavy ``numpy.``, ``subprocess.``,
   blocking ``time.sleep`` ...) unless an *allow prefix* matches first
   — the lists live in ``contracts/racecheck.json`` so widening the
   escape hatch is a reviewed diff;
3. transitively: the walk follows every corpus-resolvable call
   (``request_drain`` -> ``telemetry.incr``), and each finding carries
   the handler->...->site path so the fix target is obvious.

Opaque calls (methods on runtime objects, ``_event.set()``) are
skipped: resolving them would need type inference, and the registries
those methods live on are already covered by the L-pass.
"""

from __future__ import annotations

import ast

from .model import Corpus, Finding, ModuleModel, qualname, \
    walk_excluding_defs

#: default dotted-prefix ban list (config ``signal.ban_calls`` replaces)
DEFAULT_BAN = ("jax.", "jax.numpy.", "numpy.", "subprocess.",
               "multiprocessing.", "time.sleep", "open", "print",
               "logging.")
#: default allow list, matched before the ban list
DEFAULT_ALLOW = ("signal.", "time.monotonic", "os.getpid", "os.kill",
                 "os.write", "sys.exit", "faulthandler.")


def _handler_functions(mod: ModuleModel):
    """(handler_fndef, registration_node) for every
    ``signal.signal(sig, fn)`` whose ``fn`` is a Name bound to a def
    in this module (nested defs included — ``install`` registers a
    closure)."""
    defs_by_name: dict = {}
    for fn in mod.all_defs:
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(fn.name, []).append(fn)
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or len(node.args) < 2:
            continue
        if mod.expand(qualname(node.func)) != "signal.signal":
            continue
        target = node.args[1]
        if isinstance(target, ast.Name):
            for fn in defs_by_name.get(target.id, ()):
                out.append((fn, node))
    return out


def _matches(dotted: str, prefixes) -> bool:
    return any(dotted == p or dotted.startswith(p) for p in prefixes)


def _scan_function(mod: ModuleModel, corpus: Corpus, fn, path, allow, ban,
                   findings, visited, queue):
    """One function on the handler-reachable graph: flag unsafe sites,
    enqueue corpus-resolvable callees."""
    for node in walk_excluding_defs(fn):
        if isinstance(node, ast.With):
            for it in node.items:
                name = qualname(it.context_expr)
                if name in mod.locks and mod.locks[name] == "Lock":
                    findings.append(Finding(
                        mod.path, node.lineno, "S1",
                        f"signal-handler path {' -> '.join(path)} "
                        f"acquires non-reentrant lock '{name}' "
                        f"({mod.modname}): a signal landing while the "
                        "main thread holds it deadlocks the process — "
                        "use threading.RLock or set a flag only"))
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "acquire" and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id in mod.locks and \
                mod.locks[node.func.value.id] == "Lock":
            findings.append(Finding(
                mod.path, node.lineno, "S1",
                f"signal-handler path {' -> '.join(path)} calls "
                f"'{node.func.value.id}.acquire()' on a non-reentrant "
                "lock — self-deadlock hazard"))
            continue
        res = corpus.resolve_call(mod, node)
        kind, a, b, display = res
        if kind == "func":
            key = (a.modname, b.name, b.lineno)
            if key not in visited:
                visited.add(key)
                queue.append((a, b, path + (f"{a.modname}.{b.name}",)))
        elif kind == "external":
            if _matches(a, allow):
                continue
            if _matches(a, ban):
                findings.append(Finding(
                    mod.path, node.lineno, "S1",
                    f"signal-handler path {' -> '.join(path)} calls "
                    f"'{display}' ({a}) — not async-signal-safe "
                    "(allocation/dispatch inside a handler); defer to "
                    "the drain flag or extend signal.allow_calls with "
                    "a justification"))


def check_signals(corpus: Corpus, config: dict | None = None) -> list:
    """All S1 findings: walk the call graph from every registered
    handler."""
    cfg = (config or {}).get("signal", {})
    allow = tuple(cfg.get("allow_calls", DEFAULT_ALLOW))
    ban = tuple(cfg.get("ban_calls", DEFAULT_BAN))
    findings: list = []
    visited: set = set()
    queue: list = []
    for mod in corpus.modules.values():
        for fn, _reg in _handler_functions(mod):
            key = (mod.modname, fn.name, fn.lineno)
            if key not in visited:
                visited.add(key)
                queue.append((mod, fn, (f"{mod.modname}.{fn.name}",)))
    while queue:
        mod, fn, path = queue.pop(0)
        _scan_function(mod, corpus, fn, path, allow, ban,
                       findings, visited, queue)
    return findings
