"""Pass orchestration, config, and the justified baseline ratchet.

``contracts/racecheck.json`` pins everything reviewable about the
auditor: the analyzed paths, the signal-safety allow/ban prefixes, and
the declared state machines — widening any of them is a diff to a
committed contract, mirroring how jaxprcheck pins budgets.

``racecheck_baseline.json`` extends the shared :mod:`..baseline`
ratchet with one extra obligation: every baselined ``(file, rule)``
pair must carry a one-line justification under ``justifications``
(key ``"<file> [<rule>]"``).  A count with a missing/empty/TODO
justification fails the gate even when the ratchet itself is
satisfied — accepted debt must say *why* it is acceptable (e.g.
"main-thread-only by the CPython ``signal.signal`` constraint"), not
just that it is old.
"""

from __future__ import annotations

import json
from pathlib import Path

from .donate import check_donate
from .locks import check_locks
from .model import (RULES, Corpus, Finding, build_corpus, load_corpus,
                    pragma_rules)
from .signals import check_signals
from .states import check_states

_REPO_ROOT = Path(__file__).resolve().parents[3]
DEFAULT_CONFIG = _REPO_ROOT / "contracts" / "racecheck.json"
BASELINE_NAME = "racecheck_baseline.json"

#: analyzed when the config has no ``paths`` (repo-relative)
DEFAULT_PATHS = ("pulsar_timing_gibbsspec_tpu/runtime",
                 "pulsar_timing_gibbsspec_tpu/serve",
                 "pulsar_timing_gibbsspec_tpu/obs")


def load_config(path=None) -> dict:
    p = Path(path) if path is not None else DEFAULT_CONFIG
    if not p.exists():
        return {}
    return json.loads(p.read_text())


def run_passes(corpus: Corpus, config: dict | None = None) -> list:
    """All findings over a corpus, pragma-suppressed and sorted."""
    config = config or {}
    findings: list[Finding] = []
    findings += check_locks(corpus)
    findings += check_signals(corpus, config)
    findings += check_donate(corpus)
    findings += check_states(corpus, config)
    out = []
    for f in findings:
        mod = corpus.by_path.get(f.path)
        line = mod.line(f.line) if mod is not None else ""
        disabled = pragma_rules(line)
        if f.rule in disabled or "ALL" in disabled:
            continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def analyze_sources(sources: dict, config: dict | None = None) -> list:
    """Findings over in-memory ``{path: source}`` modules (the test
    fixture entry point — no filesystem, no config file)."""
    return run_passes(build_corpus(sources), config)


def analyze_repo(paths=None, config: dict | None = None,
                 root: Path | None = None):
    """(findings, analyzed_files) over on-disk paths; ``paths``
    defaults to the config's ``paths`` (repo-relative)."""
    root = root if root is not None else _REPO_ROOT
    config = config if config is not None else load_config()
    rels = paths if paths else config.get("paths", list(DEFAULT_PATHS))
    abspaths = [root / p if not Path(p).is_absolute() else Path(p)
                for p in rels]
    corpus = load_corpus(abspaths, root)
    return run_passes(corpus, config), sorted(corpus.by_path)


# -- the justified baseline (shared with numcheck: ..baseline) ----------------

from ..baseline import check_justifications  # noqa: E402,F401 - re-export
from ..baseline import justification_key as _just_key  # noqa: E402,F401
from ..baseline import load_justified_baseline as load_baseline_file  # noqa: E402,F401,E501
from ..baseline import write_justified_baseline as write_baseline_file  # noqa: E402,F401,E501


__all__ = ["RULES", "Finding", "analyze_repo", "analyze_sources",
           "check_justifications", "load_baseline_file", "load_config",
           "run_passes", "write_baseline_file", "BASELINE_NAME",
           "DEFAULT_CONFIG", "DEFAULT_PATHS"]
