"""Runtime guards complementing the static :mod:`.jaxlint` pass.

Three opt-in checks that catch at run time what the AST pass can only
approximate:

- :func:`no_transfers` — a context manager wiring
  ``jax.transfer_guard("disallow")`` around compiled-sweep dispatch, so a
  silent host↔device round-trip (the classic steady-state throughput
  killer) raises instead of degrading.
- :class:`RecompileCounter` / :func:`count_recompiles` — counts XLA
  backend compiles via ``jax.monitoring`` duration events.  After warmup,
  a steady sweep loop must report **zero**; any retrace is a regression
  (:mod:`..profiling` re-exports this for ``bench.py``).
- :func:`debug_nans` — scoped ``jax_debug_nans`` for CI runs chasing a
  non-finite draw back to its primitive.

All three are no-cost when unused: nothing is registered or toggled at
import time except a single idle monitoring listener.
"""

from __future__ import annotations

import contextlib
import threading

import jax

#: jax.monitoring event recorded once per XLA backend compile.  Verified
#: against jax 0.4.x: first call of a jitted fn fires >=1 of these, a
#: cache hit fires none, a retrace (new avals) fires them again.
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_active_counters: list = []
_lock = threading.Lock()
_listener_installed = False


def _install_listener():
    # jax.monitoring has no unregister-one API, so install a single
    # module-level listener lazily and fan out to active counters.
    global _listener_installed
    with _lock:
        if _listener_installed:
            return

        def _on_event(event, duration, **kwargs):
            if _COMPILE_EVENT not in event:
                return
            with _lock:
                for c in _active_counters:
                    c._bump()

        jax.monitoring.register_event_duration_secs_listener(_on_event)
        _listener_installed = True


class RecompileCounter:
    """Counts XLA backend compiles while attached.

    >>> with count_recompiles() as rc:
    ...     f(x)          # warmup compile
    ...     rc.reset()    # don't charge the warmup
    ...     f(x)          # steady state
    >>> rc.events         # 0 -> no retrace

    Phase attribution (``bench.py``): :meth:`phase` names the window every
    subsequent compile event is charged to, so warmup compiles land in
    ``per_phase["warmup"]`` instead of polluting the steady-state count
    the zero-retrace contract asserts.  Compile sites that are *expected*
    — a chunk-function cache miss paying a fresh XLA compile for a
    legitimate new (length, offset) chunk shape — bracket the triggering
    dispatch in :func:`planned_compile`; :meth:`unplanned` subtracts
    events fired inside such windows per phase, so
    ``unplanned("steady") == 0`` is the honest contract even on runs
    whose steady window legally compiles a trailing odd chunk.  (A
    window, not a count: one jit build fires a variable number of
    backend-compile events — measured 2-3 on CPU jax 0.4.x.)"""

    def __init__(self):
        self.events = 0
        self.per_phase: dict = {}
        self.planned_per_phase: dict = {}
        self._phase = None
        self._planned_depth = 0

    def _bump(self):
        self.events += 1
        if self._phase is not None:
            self.per_phase[self._phase] = \
                self.per_phase.get(self._phase, 0) + 1
            if self._planned_depth > 0:
                self.planned_per_phase[self._phase] = \
                    self.planned_per_phase.get(self._phase, 0) + 1

    def phase(self, name):
        """Start charging compile events (and planned-compile notes) to
        ``name``; returns self so ``rc.phase("warmup")`` chains."""
        self._phase = name
        self.per_phase.setdefault(name, 0)
        self.planned_per_phase.setdefault(name, 0)
        return self

    def unplanned(self, name) -> int:
        """Compile events charged to phase ``name`` that fired outside
        every :func:`planned_compile` window."""
        return max(0, self.per_phase.get(name, 0)
                   - self.planned_per_phase.get(name, 0))

    def reset(self):
        """Zero all counts (e.g. after the expected warmup compile)."""
        self.events = 0
        self.per_phase = {}
        self.planned_per_phase = {}

    @property
    def retraced(self) -> bool:
        return self.events > 0

    def attach(self):
        _install_listener()
        with _lock:
            if self not in _active_counters:
                _active_counters.append(self)
        return self

    def detach(self):
        with _lock:
            if self in _active_counters:
                _active_counters.remove(self)
        return self


@contextlib.contextmanager
def count_recompiles():
    """Context manager yielding an attached :class:`RecompileCounter`."""
    rc = RecompileCounter().attach()
    try:
        yield rc
    finally:
        rc.detach()


@contextlib.contextmanager
def planned_compile():
    """Mark every compile event fired inside the block as *planned* on
    all attached counters (e.g. around the dispatch of a chunk function
    whose cache lookup just missed).  Phase-scoped retrace contracts
    (``unplanned("steady") == 0``) then don't charge legitimate
    compiles.  No-op when nothing is attached.

    The depth bump is process-global (events arrive on whatever thread
    executes the dispatch — e.g. the watchdog worker), so only bracket
    blocking regions that genuinely end with the compile done."""
    with _lock:
        bumped = list(_active_counters)
        for c in bumped:
            c._planned_depth += 1
    try:
        yield
    finally:
        with _lock:
            for c in bumped:
                c._planned_depth -= 1


@contextlib.contextmanager
def no_transfers(level: str = "disallow"):
    """Forbid implicit host<->device transfers inside the block.

    Wrap the *dispatch* of an already-compiled sweep (all arguments
    device-resident) — not warmup, which legitimately transfers while
    staging constants.  Explicit transfers (``jax.device_put``,
    ``jnp.asarray(numpy_array)``, ``np.asarray(device_array)``) stay
    allowed under ``"disallow"``; only implicit conversions raise.

    ``level`` may be ``"disallow"`` (raise), ``"log"`` (warn, for
    soak runs), or ``"allow"`` (temporarily opt back out inside an
    enclosing guard).
    """
    with jax.transfer_guard(level):
        yield


@contextlib.contextmanager
def debug_nans(enable: bool = True):
    """Scoped ``jax_debug_nans``: re-runs the offending primitive
    un-jitted and raises at the first non-finite output.  Expensive —
    CI/debug only, never in benchmarked paths."""
    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", bool(enable))
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)
