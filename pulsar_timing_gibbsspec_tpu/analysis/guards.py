"""Runtime guards complementing the static :mod:`.jaxlint` pass.

Three opt-in checks that catch at run time what the AST pass can only
approximate:

- :func:`no_transfers` — a context manager wiring
  ``jax.transfer_guard("disallow")`` around compiled-sweep dispatch, so a
  silent host↔device round-trip (the classic steady-state throughput
  killer) raises instead of degrading.
- :class:`RecompileCounter` / :func:`count_recompiles` — counts XLA
  backend compiles via ``jax.monitoring`` duration events.  After warmup,
  a steady sweep loop must report **zero**; any retrace is a regression
  (:mod:`..profiling` re-exports this for ``bench.py``).
- :func:`debug_nans` — scoped ``jax_debug_nans`` for CI runs chasing a
  non-finite draw back to its primitive.

All three are no-cost when unused: nothing is registered or toggled at
import time except a single idle monitoring listener.
"""

from __future__ import annotations

import contextlib
import threading

import jax

#: jax.monitoring event recorded once per XLA backend compile.  Verified
#: against jax 0.4.x: first call of a jitted fn fires >=1 of these, a
#: cache hit fires none, a retrace (new avals) fires them again.
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_active_counters: list = []
_lock = threading.Lock()
_listener_installed = False


def _install_listener():
    # jax.monitoring has no unregister-one API, so install a single
    # module-level listener lazily and fan out to active counters.
    global _listener_installed
    with _lock:
        if _listener_installed:
            return

        def _on_event(event, duration, **kwargs):
            if _COMPILE_EVENT not in event:
                return
            with _lock:
                for c in _active_counters:
                    c._bump()

        jax.monitoring.register_event_duration_secs_listener(_on_event)
        _listener_installed = True


class RecompileCounter:
    """Counts XLA backend compiles while attached.

    >>> with count_recompiles() as rc:
    ...     f(x)          # warmup compile
    ...     rc.reset()    # don't charge the warmup
    ...     f(x)          # steady state
    >>> rc.events         # 0 -> no retrace
    """

    def __init__(self):
        self.events = 0

    def _bump(self):
        self.events += 1

    def reset(self):
        """Zero the count (e.g. after the expected warmup compile)."""
        self.events = 0

    @property
    def retraced(self) -> bool:
        return self.events > 0

    def attach(self):
        _install_listener()
        with _lock:
            if self not in _active_counters:
                _active_counters.append(self)
        return self

    def detach(self):
        with _lock:
            if self in _active_counters:
                _active_counters.remove(self)
        return self


@contextlib.contextmanager
def count_recompiles():
    """Context manager yielding an attached :class:`RecompileCounter`."""
    rc = RecompileCounter().attach()
    try:
        yield rc
    finally:
        rc.detach()


@contextlib.contextmanager
def no_transfers(level: str = "disallow"):
    """Forbid implicit host<->device transfers inside the block.

    Wrap the *dispatch* of an already-compiled sweep (all arguments
    device-resident) — not warmup, which legitimately transfers while
    staging constants.  Explicit transfers (``jax.device_put``,
    ``jnp.asarray(numpy_array)``, ``np.asarray(device_array)``) stay
    allowed under ``"disallow"``; only implicit conversions raise.

    ``level`` may be ``"disallow"`` (raise), ``"log"`` (warn, for
    soak runs), or ``"allow"`` (temporarily opt back out inside an
    enclosing guard).
    """
    with jax.transfer_guard(level):
        yield


@contextlib.contextmanager
def debug_nans(enable: bool = True):
    """Scoped ``jax_debug_nans``: re-runs the offending primitive
    un-jitted and raises at the first non-finite output.  Expensive —
    CI/debug only, never in benchmarked paths."""
    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", bool(enable))
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)
