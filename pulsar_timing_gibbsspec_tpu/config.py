"""Global execution configuration.

The reference implementation (``pulsar_gibbs.py``) is float64 NumPy on a
single CPU.  On TPU, float64 is software-emulated: the batched 45x160x160
Cholesky at the heart of the sweep measures ~2500x slower in f64 than f32 on
v5e.  The device path therefore defaults to float32 and makes it safe with
Jacobi (diagonal) preconditioning of ``Sigma = T^T N^-1 T + diag(phi^-1)``
(see ``ops/linalg.py``), which reduces the condition number by several orders
of magnitude.  ``settings.precision = "f64"`` forces double precision for
validation runs; the NumPy oracle backend is always float64.
"""

from __future__ import annotations

import dataclasses
import os


@dataclasses.dataclass
class Settings:
    """Process-wide knobs (read at model-compile time, not per-op)."""

    #: device compute precision: "f32" (default, preconditioned) or "f64"
    precision: str = os.environ.get("PTGIBBS_PRECISION", "f32")

    #: sweeps per device dispatch in the jitted sampler (chain is written
    #: back to host every chunk; also the checkpoint cadence)
    chunk_size: int = 100

    #: number of grid points for the numerical rho_k conditional CDF
    #: (reference uses 1000, pulsar_gibbs.py:228)
    rho_grid_size: int = 1000

    def apply(self):
        """Push precision into the JAX config.  Called once at model-compile
        entry (not from dtype accessors — enabling x64 is a process-wide,
        effectively one-way switch that must precede any traced op)."""
        if self.precision == "f64":
            import jax

            jax.config.update("jax_enable_x64", True)

    def real_dtype(self):
        import jax.numpy as jnp

        return jnp.float64 if self.precision == "f64" else jnp.float32


settings = Settings()
