"""Global execution configuration.

The reference implementation (``pulsar_gibbs.py``) is float64 NumPy on a
single CPU.  On TPU, float64 is software-emulated: the batched 45x160x160
Cholesky at the heart of the sweep measures ~2500x slower in f64 than f32 on
v5e.  The device path therefore defaults to float32 and makes it safe with
Jacobi (diagonal) preconditioning of ``Sigma = T^T N^-1 T + diag(phi^-1)``
(see ``ops/linalg.py``), which reduces the condition number by several orders
of magnitude.  ``settings.precision = "f64"`` forces double precision for
validation runs; the NumPy oracle backend is always float64.
"""

from __future__ import annotations

import dataclasses
import os


class SettingsError(ValueError):
    """A malformed process-wide setting — a bad constructor value or a
    bad ``PTGIBBS_*`` environment override.  Typed so callers can tell
    configuration mistakes from genuine ValueErrors in model code."""


def _env_choice(env: str, default: str, choices: tuple) -> str:
    """A closed-vocabulary environment override, validated at read time
    (Settings construction) instead of failing obscurely at the first
    kernel dispatch."""
    raw = os.environ.get(env, default)
    val = str(raw).strip().lower()
    if val not in choices:
        raise SettingsError(
            f"{env}={raw!r} must be one of {sorted(choices)}")
    return val


def _env_int(env: str, default: str) -> int:
    """A positive-integer environment override, validated at read time
    (Settings construction) instead of failing obscurely deep inside a
    segmented-Gram reshape."""
    raw = os.environ.get(env, default)
    try:
        val = int(str(raw).strip())
    except (TypeError, ValueError) as e:
        raise SettingsError(
            f"{env}={raw!r} is not an integer") from e
    if val <= 0:
        raise SettingsError(
            f"{env}={val} must be a positive integer")
    return val


@dataclasses.dataclass
class Settings:
    """Process-wide knobs (read at model-compile time, not per-op)."""

    #: storage precision of the large device arrays (basis matrices,
    #: residuals): "f32" (default) or "f64"
    precision: str = os.environ.get("PTGIBBS_PRECISION", "f32")

    #: compute precision for sampler state, reductions and factorizations:
    #: "f64" (default) or "f32".  Mixed f32-storage/f64-compute is the
    #: validated scheme: the conditional means Sigma^-1 d lose ~kappa*eps
    #: relative accuracy, and kappa ~ 1e4 makes f32 means wrong at the
    #: several-percent level on the smallest Fourier coefficients (which
    #: biases the rho_k conditional); f64 compute on f32 data is exact to
    #: ~1e-7 data precision while the flop-heavy einsums keep f32 inputs.
    compute_precision: str = os.environ.get("PTGIBBS_COMPUTE", "f64")

    #: sweeps per device dispatch in the jitted sampler (chain is written
    #: back to host every chunk; also the checkpoint cadence)
    chunk_size: int = 100

    #: dtype of the recorded per-sweep states shipped device->host:
    #: "f32" (storage dtype, default) or "bf16" (halves the dominant
    #: transfer for bandwidth-starved device links).  Rounds the RECORD
    #: only: carries/checkpoints stay exact and resume is bitwise within
    #: a run; models with red-MH DE jumps see the rounded rows in the DE
    #: history, so their realized proposal stream differs from an
    #: f32-record run at rounding level (stationarity unaffected) — see
    #: jax_backend.JaxGibbsDriver for the full statement
    record_precision: str = os.environ.get("PTGIBBS_RECORD", "f32")

    #: number of grid points for the numerical rho_k conditional CDF
    #: (reference uses 1000, pulsar_gibbs.py:228)
    rho_grid_size: int = 1000

    #: TOA-segment length of the segmented-f32 MXU Gram
    #: (sampler/jax_backend.tnt_d_seg).  Error model: f32 accumulation
    #: inside a segment of ~seg TOAs is bounded (Cauchy-Schwarz, relative
    #: to the Jacobi scale sqrt(G_bb G_cc)) by ~sqrt(seg)*eps_f32 —
    #: measured 2.5e-7 on the 45-pulsar bench state at seg=96, an order
    #: below the preconditioned system's smallest eigenvalue (~4.5e-6),
    #: so factors of the resulting Sigma stay safely positive definite
    #: while the einsum runs ~60x faster than f64 accumulation.
    gram_seg_len: int = dataclasses.field(
        default_factory=lambda: _env_int("PTGIBBS_GRAM_SEG", "96"))

    #: TOA-segment length of the segmented EXACT Gram
    #: (sampler/jax_backend.tnt_d): per-segment f64-accumulated partial
    #: Grams over f32 operands, reduced over segments in f64.  Error
    #: model: every f32*f32 product is exactly representable in f64, so
    #: the only difference from a monolithic f64 accumulation is the f64
    #: partial-sum ORDER — a <= 1 ULP reassociation class, NOT the f32
    #: O(sqrt(seg)*eps_f32) class of gram_seg_len above.  What segmenting
    #: buys is compile-time memory: XLA's widening dot_general otherwise
    #: materializes a ceil(N/seg)-segment operand-copy scratch (the
    #: 15.8 GiB C=128 wall, analysis/jaxprcheck/hbm.py); with the contract
    #: dimension bounded by this length the scratch collapses to one
    #: segment.  96 keeps the jaxprcheck HBM scratch model's calibration
    #: (hbm.DEFAULT_SEG_LEN) aligned with the program it audits.
    gram_seg_len_exact: int = dataclasses.field(
        default_factory=lambda: _env_int("PTGIBBS_GRAM_SEG_EXACT", "96"))

    #: mixed-precision mode of the structured correlated-ORF joint b-draw
    #: (sampler/jax_backend.draw_b_joint_structured): when on, the steady
    #: (exact=False) draw factors both stages with the two-float MXU
    #: kernel — an f32 factorization plus one iterative-refinement step
    #: (ops/linalg.tf_chol_factor's residual congruence correction, the
    #: same pattern as the segmented f32 Gram) — carrying the accepted,
    #: condition-independent O(n*eps_f32) error class the sequential HD
    #: kernel already KS-validated.  Off forces the f64 blocked factor
    #: everywhere.  Warmup/refresh draws (exact=True) are always f64
    #: regardless of this flag (the breakdown-margin contract).
    joint_mixed: bool = os.environ.get("PTGIBBS_JOINT_MIXED", "1") != "0"

    #: kernel tier of the sweep's hot linear algebra (ops/kernels): the
    #: fused Pallas/Mosaic chol->solve->sample and segment-streamed Gram
    #: kernels vs their pure-XLA reference twins.  "auto" (default)
    #: resolves to "pallas" on a TPU backend when Pallas imports and to
    #: "xla" everywhere else; an explicit "pallas" off-TPU runs the
    #: kernels in interpret mode (the CPU parity-test story) and
    #: degrades to "xla" when Pallas is unavailable.  Resolved from
    #: static Python at trace time — changing it retraces once, never
    #: inside the steady loop.  Only the f32 steady bodies ever route to
    #: Mosaic; the f64/two-float exact bodies are XLA-tier by design
    #: (docs/PERFORMANCE.md section 9).
    kernel_tier: str = dataclasses.field(
        default_factory=lambda: _env_choice(
            "PTGIBBS_KERNEL_TIER", "auto", ("pallas", "xla", "auto")))

    #: mega-chunk factor of the steady loop (sampler/jax_backend): one
    #: device dispatch scans this many chunk_size sub-chunks back to
    #: back, with the carry donated end-to-end — host work per dispatch
    #: becomes a single enqueue, amortizing the ~100 ms dispatch tax
    #: over megachunk*chunk_size sweeps.  The sampled process is
    #: bitwise-identical for every value (per-sweep keys are pure in the
    #: absolute iteration index); 1 (the default) is the legacy
    #: one-chunk-per-dispatch loop.  Models with a red-hyper MH block
    #: are bounded by the DE history delay: (2*megachunk - 1) *
    #: chunk_size <= DE_DELAY - DE_Q (see docs/PERFORMANCE.md).
    megachunk: int = int(os.environ.get("PTGIBBS_MEGACHUNK", "1"))

    #: persistent XLA compilation cache (first 45-pulsar compile costs
    #: minutes through the remote-compile tunnel; cached reruns are free).
    #: Empty string disables.
    compile_cache: str = os.environ.get("PTGIBBS_CACHE",
                                        os.path.expanduser("~/.cache/ptgibbs_xla"))

    #: ensemble mixing stage (sampler/ensemble.py): interchain
    #: Goodman-Weare stretch moves on the common-spectrum rho block plus
    #: an ASIS ancillary grid redraw, appended to each steady sweep.
    #: Off (the default) traces exactly the pre-ensemble chunk program —
    #: the stage is Python-gated, not lax.cond-gated, so off means the
    #: ops never enter the jaxpr (contracts/crn_quick.json pins this).
    ensemble: bool = os.environ.get("PTGIBBS_ENSEMBLE", "0") != "0"

    #: parallel-tempering ladder depth T over a temperature sub-axis of
    #: the chain batch (chain c runs at inverse temperature
    #: betas[c % T]; only the beta=1 chains c % T == 0 are posterior
    #: samples).  1 disables tempering; requires ``ensemble`` on.
    pt_ladder: int = int(os.environ.get("PTGIBBS_PT_LADDER", "1"))

    def __post_init__(self):
        # segment lengths feed reshape/pad arithmetic in the segmented
        # Grams — a zero, negative, or fractional length would surface
        # as an opaque shape error deep inside tracing, so reject it
        # here with a typed, named error instead
        for name in ("gram_seg_len", "gram_seg_len_exact"):
            v = getattr(self, name)
            if isinstance(v, bool) or not isinstance(v, int) or v <= 0:
                raise SettingsError(
                    f"settings.{name}={v!r} must be a positive integer "
                    "(env: PTGIBBS_GRAM_SEG / PTGIBBS_GRAM_SEG_EXACT)")
        # the kernel tier gates dispatch to compiled accelerator kernels
        # — a typo'd tier would otherwise fall through a string compare
        # and silently run the slow path forever
        kt = self.kernel_tier
        if not isinstance(kt, str) or kt not in ("pallas", "xla", "auto"):
            raise SettingsError(
                f"settings.kernel_tier={kt!r} must be one of "
                "['auto', 'pallas', 'xla'] (env: PTGIBBS_KERNEL_TIER)")

    def apply(self):
        """Push precision into the JAX config.  Called once at model-compile
        entry (not from dtype accessors — enabling x64 is a process-wide,
        effectively one-way switch that must precede any traced op)."""
        import jax

        if self.precision == "f64" or self.compute_precision == "f64":
            jax.config.update("jax_enable_x64", True)
        if self.compile_cache:
            os.makedirs(self.compile_cache, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", self.compile_cache)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    def real_dtype(self):
        import jax.numpy as jnp

        return jnp.float64 if self.precision == "f64" else jnp.float32

    def compute_dtype(self):
        import jax.numpy as jnp

        return (jnp.float64 if self.compute_precision == "f64"
                else self.real_dtype())


settings = Settings()


#: pinned per-backend dispatch-geometry defaults emitted by
#: ``tools/autotune.py`` (chunk, megachunk, nchains, gram_seg_len per
#: backend, selected by measured amortized dispatch cost)
AUTOTUNE_TABLE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "AUTOTUNE.json")


def autotune_defaults(backend: str | None = None, path: str | None = None):
    """The pinned autotune row for ``backend`` (default: the current JAX
    backend), or None when no table/row exists.  Consulted by the driver
    ONLY when ``PTGIBBS_AUTOTUNE`` is set in the environment — the
    committed table can never perturb a run that did not opt in."""
    import json

    path = path or AUTOTUNE_TABLE
    if not os.path.exists(path):
        return None
    try:
        with open(path) as fh:
            table = json.load(fh)
    except Exception as e:  # noqa: BLE001 — a torn table is a config error
        raise SettingsError(f"unreadable autotune table {path}: {e}") from e
    if backend is None:
        import jax

        try:
            backend = jax.default_backend()
        except Exception:  # noqa: BLE001
            backend = "cpu"
    row = (table.get("backends") or {}).get(backend)
    return dict(row["best"]) if row and row.get("best") else None
