"""Gibbs vs MH autocorrelation comparison — the method's selling point.

Script form of the reference's ``pta_gibbs_freespec.ipynb`` validation
(cells 31-39): sample the same free-spectrum posterior with (a) the
blocked Gibbs sampler and (b) a standard adaptive random-walk MH on the
b-marginalized likelihood (the role PTMCMC plays in the reference), then
compare per-parameter integrated autocorrelation times.  Gibbs draws the
rho block from its exact conditional, so its ACT per rho channel is O(1)
while the random walk's is O(100) — the reference's headline plot
(cell 39) as a table.

Runs in ~3 min on CPU:  ``python examples/gibbs_vs_mh_act.py``
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

REFDATA = os.environ.get("PTGIBBS_REFDATA", "/root/reference/simulated_data")


def adaptive_mh(lnpost, x0, niter, rng, adapt_every=200):
    """Plain adaptive random-walk MH (the reference's PTMCMC stand-in):
    Gaussian proposals from the running empirical covariance with the
    2.38/sqrt(d) AM scaling."""
    d = len(x0)
    x = x0.copy()
    lp = lnpost(x)
    cov = np.eye(d) * 0.01 ** 2
    L = np.linalg.cholesky(cov)
    chain = np.zeros((niter, d))
    acc = 0
    for ii in range(niter):
        q = x + (2.38 / np.sqrt(d)) * (L @ rng.standard_normal(d))
        lq = lnpost(q)
        if np.log(rng.uniform()) < lq - lp:
            x, lp = q, lq
            acc += 1
        chain[ii] = x
        if ii and ii % adapt_every == 0 and ii < niter // 2:
            emp = np.cov(chain[ii // 2:ii].T) + 1e-10 * np.eye(d)
            try:
                L = np.linalg.cholesky(emp)
            except np.linalg.LinAlgError:
                pass
    return chain, acc / niter


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--gibbs-iters", type=int, default=1500)
    ap.add_argument("--mh-iters", type=int, default=15000)
    ap.add_argument("--psr", default="J1713+0747")
    ap.add_argument("--nbins", type=int, default=10)
    args = ap.parse_args()

    from pulsar_timing_gibbsspec_tpu import PulsarBlockGibbs, model_general
    from pulsar_timing_gibbsspec_tpu.data import load_pulsar
    from pulsar_timing_gibbsspec_tpu.ops.acf import integrated_act
    from pulsar_timing_gibbsspec_tpu.sampler.blocks import BlockIndex
    from pulsar_timing_gibbsspec_tpu.sampler.numpy_backend import NumpyGibbs

    psr = load_pulsar(f"{REFDATA}/{args.psr}.par", f"{REFDATA}/{args.psr}.tim",
                      inject=dict(log10_A=np.log10(2e-15), gamma=13.0 / 3.0,
                                  nmodes=args.nbins))
    pta = model_general([psr], tm_svd=True, red_var=False, white_vary=False,
                        common_psd="spectrum", common_components=args.nbins)
    idx = BlockIndex.build(pta.param_names)
    x0 = pta.initial_sample(np.random.default_rng(0))

    print(f"[1/2] Gibbs: {args.gibbs_iters} sweeps")
    gibbs = PulsarBlockGibbs(pta, backend="numpy", seed=3, progress=False)
    gchain = gibbs.sample(x0, outdir="./chains_act_demo",
                          niter=args.gibbs_iters)

    print(f"[2/2] adaptive random-walk MH: {args.mh_iters} steps on the "
          "marginalized likelihood")
    # lnlike_fullmarg seeds the oracle's Gram cache itself on first call
    # (white noise is fixed here, so the cache stays valid throughout)
    oracle = NumpyGibbs(pta, seed=4)

    def lnpost(x):
        lp = pta.get_lnprior(x)
        if not np.isfinite(lp):
            return -np.inf
        # white noise is fixed (white_vary=False) so the cached Gram stays
        # valid across evaluations; only rho moves, and it enters through
        # phi — skipping the per-call invalidate drops the dominant
        # O(n_toa W^2) rebuild from every MH step
        return oracle.lnlike_fullmarg(x) + lp

    mchain, rate = adaptive_mh(lnpost, x0, args.mh_iters,
                               np.random.default_rng(5))
    print(f"MH acceptance rate: {rate:.2f}")

    gb = gchain[args.gibbs_iters // 5:]
    mb = mchain[args.mh_iters // 5:]
    print(f"\n{'rho bin':>8s} {'Gibbs ACT':>10s} {'MH ACT':>10s} "
          f"{'ratio':>7s}")
    ratios = []
    for j, k in enumerate(idx.rho):
        ga = integrated_act(gb[:, k])
        ma = integrated_act(mb[:, k])
        ratios.append(ma / ga)
        print(f"{j:8d} {ga:10.1f} {ma:10.1f} {ma / ga:7.1f}")
    print(f"\nmedian ACT ratio (MH/Gibbs): {np.median(ratios):.1f}x "
          "— the exact conditional rho draw decorrelates in O(1) sweeps")


if __name__ == "__main__":
    main()
