"""Free-spectrum injection recovery (violin-plot data).

Script form of the reference's ``singlepulsar_sim_A2e-15_gamma4.333.ipynb``
(cells 7-16): inject a GWB power law (A = 2e-15, gamma = 13/3) into a
simulated pulsar, recover the 30-bin free spectrum with the Gibbs sampler,
and compare each bin's posterior against the injected power law.  The
notebook renders violins; this script writes the per-bin posterior
quantiles as CSV (plus a PNG when matplotlib is importable) and prints the
recovery table — the violin-plot data, without a display dependency.

Runs in ~2 min on CPU:  ``python examples/injection_recovery.py``
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

REFDATA = os.environ.get("PTGIBBS_REFDATA", "/root/reference/simulated_data")
LOG10_A, GAMMA, NMODES = np.log10(2e-15), 13.0 / 3.0, 30


def injected_log10_rho(pta):
    """Injected per-bin log10 rho from the power law (the notebook's
    injected line, cell 16)."""
    from pulsar_timing_gibbsspec_tpu.models.psd import powerlaw

    sig = next(s for s in pta.model(0).signals if "gw" in s.name)
    f = sig.freqs[::2]
    df = sig._df[::2]
    return 0.5 * np.log10(powerlaw(f, df, log10_A=LOG10_A, gamma=GAMMA))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--niter", type=int, default=2000)
    ap.add_argument("--psr", default="J1713+0747")
    ap.add_argument("--backend", default="jax", choices=["jax", "numpy"])
    ap.add_argument("--out", default="./injection_recovery")
    args = ap.parse_args()

    from pulsar_timing_gibbsspec_tpu import PulsarBlockGibbs, model_general
    from pulsar_timing_gibbsspec_tpu.data import load_pulsar
    from pulsar_timing_gibbsspec_tpu.sampler.blocks import BlockIndex

    psr = load_pulsar(f"{REFDATA}/{args.psr}.par", f"{REFDATA}/{args.psr}.tim",
                      inject=dict(log10_A=LOG10_A, gamma=GAMMA,
                                  nmodes=NMODES, seed=42))
    # notebook cell 7: constant EFAC=1 + 30-bin common spectrum + SVD TM
    pta = model_general([psr], tm_svd=True, red_var=False, white_vary=False,
                        common_psd="spectrum", common_components=NMODES)
    gibbs = PulsarBlockGibbs(pta, backend=args.backend, seed=1)
    x0 = gibbs.initial_sample(np.random.default_rng(1))
    chain = gibbs.sample(x0, outdir=args.out + "_chains", niter=args.niter)

    burn = args.niter // 5
    idx = BlockIndex.build(pta.param_names)
    inj = injected_log10_rho(pta)
    qs = np.quantile(chain[burn:, idx.rho], [0.05, 0.16, 0.5, 0.84, 0.95],
                     axis=0)

    os.makedirs(args.out, exist_ok=True)
    csv = os.path.join(args.out, "freespec_posterior.csv")
    with open(csv, "w") as fh:
        fh.write("bin,injected_log10rho,q05,q16,q50,q84,q95\n")
        for k in range(len(idx.rho)):
            fh.write(f"{k},{inj[k]:.4f}," +
                     ",".join(f"{qs[j, k]:.4f}" for j in range(5)) + "\n")
    print(f"wrote {csv}")

    within = np.mean((inj >= qs[0]) & (inj <= qs[4]))
    print(f"\ninjected power law inside the 90% band in "
          f"{100 * within:.0f}% of bins "
          f"(constrained low-frequency bins should all cover)")
    print(f"{'bin':>4s} {'injected':>9s} {'median':>9s} {'q16':>9s} "
          f"{'q84':>9s}")
    for k in range(len(idx.rho)):
        print(f"{k:4d} {inj[k]:9.2f} {qs[2, k]:9.2f} {qs[1, k]:9.2f} "
              f"{qs[3, k]:9.2f}")

    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        fig, ax = plt.subplots(figsize=(9, 4))
        parts = ax.violinplot(
            [chain[burn:, c] for c in idx.rho],
            positions=np.arange(len(idx.rho)), widths=0.8,
            showextrema=False)
        ax.plot(np.arange(len(idx.rho)), inj, "k--", lw=1.5,
                label=f"injected A=2e-15, gamma=13/3")
        ax.set_xlabel("frequency bin")
        ax.set_ylabel(r"$\log_{10}\rho$")
        ax.legend()
        png = os.path.join(args.out, "freespec_violin.png")
        fig.savefig(png, dpi=120, bbox_inches="tight")
        print(f"wrote {png}")
    except ImportError:
        print("matplotlib not importable; skipped the PNG")


if __name__ == "__main__":
    main()
