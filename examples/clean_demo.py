"""Canonical end-to-end single-pulsar free-spectrum run.

Script form of the reference's ``clean_demo.ipynb`` (cells 3-9): load a
pulsar, build the ``model_general`` free-spectrum model with varying
per-backend white noise, run the blocked Gibbs sampler, and print a
posterior summary.  The reference notebook points at a NANOGrav 9-yr data
file it does not ship; here the 45-pulsar simulated corpus stands in (set
``PTGIBBS_REFDATA`` to point elsewhere).

Runs in ~2 min on CPU:  ``python examples/clean_demo.py [--niter N]``
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

REFDATA = os.environ.get("PTGIBBS_REFDATA", "/root/reference/simulated_data")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--niter", type=int, default=1500)
    ap.add_argument("--psr", default="J1713+0747")
    ap.add_argument("--backend", default="jax", choices=["jax", "numpy"])
    ap.add_argument("--outdir", default="./chains_clean_demo")
    ap.add_argument("--npz", default=None, metavar="SNAPSHOT",
                    help="load a recorded enterprise.Pulsar attribute "
                    "surface (.npz, see tools/make_enterprise_snapshot.py) "
                    "through the from_enterprise adapter instead of the "
                    "par/tim loader — the reference's real-data path "
                    "(clean_demo.ipynb cells 3-5)")
    args = ap.parse_args()

    from pulsar_timing_gibbsspec_tpu import PulsarBlockGibbs, model_general
    from pulsar_timing_gibbsspec_tpu.data import (load_enterprise_snapshot,
                                                  load_pulsar)

    if args.npz:
        # reference clean_demo cell 3 with a real timing solution:
        # enterprise.Pulsar attribute surface -> from_enterprise
        psr = load_enterprise_snapshot(args.npz)
    else:
        # reference clean_demo cell 3: Pulsar(par, tim)
        psr = load_pulsar(f"{REFDATA}/{args.psr}.par",
                          f"{REFDATA}/{args.psr}.tim",
                          inject=dict(log10_A=np.log10(2e-15),
                                      gamma=13.0 / 3.0, nmodes=30))
    # cell 5: model_general(red_var=False, white_vary=True,
    #                       common_psd='spectrum', common_components=10)
    pta = model_general([psr], tm_svd=True, red_var=False, white_vary=True,
                        common_psd="spectrum", common_components=10)
    # cells 7-9: PulsarBlockGibbs(pta) -> sample
    gibbs = PulsarBlockGibbs(pta, backend=args.backend, seed=0)
    x0 = gibbs.initial_sample(np.random.default_rng(0))
    chain = gibbs.sample(x0, outdir=args.outdir, niter=args.niter)

    burn = args.niter // 5
    print(f"\nposterior summary ({args.niter - burn} post-burn samples):")
    print(f"{'parameter':<42s} {'median':>9s} {'16%':>9s} {'84%':>9s}")
    for k, name in enumerate(gibbs.param_names):
        q16, q50, q84 = np.quantile(chain[burn:, k], [0.16, 0.5, 0.84])
        print(f"{name:<42s} {q50:9.3f} {q16:9.3f} {q84:9.3f}")
    print(f"\nchain files in {args.outdir}/")


if __name__ == "__main__":
    main()
