"""Hellings-Downs PTA free-spectrum sampling — beyond the reference.

The reference's model factory can build Hellings-Downs-correlated common
processes (``model_definition.py:198-216``) but its experimental PTA
sampler only ever handles the uncorrelated-CRN case
(``pta_gibbs.py:533`` assumes a block-diagonal phi).  This framework
samples the correlated model exactly: a joint cross-pulsar b-draw (dense
for small arrays, sequential pulsar-wise past HD_DENSE_MAX (64) total coefficients) and the
quadratic-form rho_k conditional ``p(rho | a) ~ rho^-P exp(-taut/rho)``
with ``taut = 0.5 sum_phase a^T G^-1 a``.

Runs in ~3 min on CPU:  ``python examples/hd_pta_demo.py``
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

REFDATA = os.environ.get("PTGIBBS_REFDATA", "/root/reference/simulated_data")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--niter", type=int, default=1200)
    ap.add_argument("--npsr", type=int, default=6)
    ap.add_argument("--nbins", type=int, default=5)
    ap.add_argument("--backend", default="jax", choices=["jax", "numpy"])
    ap.add_argument("--red", action="store_true",
                    help="add per-pulsar intrinsic red free spectra "
                    "(correlated gw keeps its own basis columns)")
    ap.add_argument("--orf", default="hd",
                    help="hd | freq_hd | st | gw_dipole | gw_monopole, or "
                    "the parameterized bin_orf / legendre_orf (sampled "
                    "correlation weights)")
    args = ap.parse_args()

    from pulsar_timing_gibbsspec_tpu import model_general
    from pulsar_timing_gibbsspec_tpu.data import load_directory
    from pulsar_timing_gibbsspec_tpu.models.orf import hd
    from pulsar_timing_gibbsspec_tpu.sampler.blocks import BlockIndex
    from pulsar_timing_gibbsspec_tpu.sampler.gibbs import PTABlockGibbs

    psrs = load_directory(
        REFDATA, inject=dict(log10_A=np.log10(2e-15), gamma=13.0 / 3.0))
    psrs = psrs[:args.npsr]
    print(f"{len(psrs)} pulsars; HD correlation range over pairs: "
          f"[{min(hd(a.pos, b.pos) for i, a in enumerate(psrs) for b in psrs[i+1:]):.2f}, "
          f"{max(hd(a.pos, b.pos) for i, a in enumerate(psrs) for b in psrs[i+1:]):.2f}]")

    pta = model_general(psrs, tm_svd=True, red_var=args.red,
                        red_psd="spectrum", red_components=args.nbins,
                        white_vary=False,
                        common_psd="spectrum", common_components=args.nbins,
                        orf=args.orf)
    gibbs = PTABlockGibbs(pta, backend=args.backend, seed=0)
    x0 = gibbs.initial_sample(np.random.default_rng(0))
    chain = gibbs.sample(x0, outdir="./chains_hd_demo", niter=args.niter)

    burn = args.niter // 5
    idx = BlockIndex.build(pta.param_names)
    print(f"\nHD common free spectrum ({args.niter - burn} post-burn "
          f"samples):")
    print(f"{'bin':>4s} {'median':>9s} {'16%':>9s} {'84%':>9s}")
    for j, k in enumerate(idx.rho):
        q16, q50, q84 = np.quantile(chain[burn:, k], [0.16, 0.5, 0.84])
        print(f"{j:4d} {q50:9.2f} {q16:9.2f} {q84:9.2f}")
    if len(idx.orf):
        print("\nsampled ORF weights (median [16%, 84%]):")
        for k in idx.orf:
            q16, q50, q84 = np.quantile(chain[burn:, k], [0.16, 0.5, 0.84])
            print(f"  {pta.param_names[k]:36s} {q50:6.2f} "
                  f"[{q16:6.2f}, {q84:6.2f}]")
    print("\nchain files in ./chains_hd_demo/")


if __name__ == "__main__":
    main()
